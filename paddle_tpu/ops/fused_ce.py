"""Fused (chunked) linear + softmax cross-entropy over a tied vocab head.

Reference parity: the reference fuses softmax+CE in
paddle/phi/kernels/gpu/cross_entropy_kernel.cu (softmax_with_cross_entropy)
and caps logit memory via its fused attention/CE ops; this is the TPU-native
generalization that also folds in the unembedding matmul.

Why: for a [B, S, H] activation and a [V, H] tied embedding, materializing
logits [B, S, V] is the single largest HBM tenant of a GPT train step
(2.1 GB bf16 + 4.3 GB f32 cotangent at B=32, S=1024, V=32k) and is what
knocks the step off its throughput scaling. This op scans the sequence in
chunks: forward computes per-chunk logits -> logsumexp -> picked logit and
keeps ONLY the [B, S] logsumexp; backward recomputes each chunk's logits
(one extra [chunk, V] matmul — FLOPs traded for HBM, the same deal as flash
attention) and accumulates dW in f32. Peak head memory drops from
O(B*S*V) to O(B*S*V / n_chunks).

The chunk axis is the SEQUENCE, with batch left intact, so a dp-sharded
batch stays perfectly data-parallel under GSPMD (each scan step is a
[B, c, H] x [H, V] matmul sharded over dp; no resharding of the scanned
operand).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _pick_chunks(B, S, V, n_chunks):
    """Choose a sequence-chunk count: cap per-chunk f32 logits near 256 MB.
    n_chunks None or <1 means auto."""
    if n_chunks is not None and int(n_chunks) >= 1:
        n = int(n_chunks)
    else:
        budget = 256e6
        n = 1
        while (B * (S // n) * V * 4 > budget and n < S and S % (n * 2) == 0):
            n *= 2
    while S % n:
        n -= 1
    return max(n, 1)


def _chunk_logits(xc, w):
    """[B, c, H] x [V, H] -> [B, c, V] with f32 MXU accumulation."""
    return jax.lax.dot_general(
        xc, w, (((2,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_ce(x, w, labels, n):
    return _fused_ce_fwd(x, w, labels, n)[0]


def _fused_ce_fwd(x, w, labels, n):
    B, S, H = x.shape
    c = S // n
    xr = jnp.moveaxis(x.reshape(B, n, c, H), 1, 0)        # [n, B, c, H]
    lr = jnp.moveaxis(labels.reshape(B, n, c), 1, 0)      # [n, B, c]

    def f(acc, inp):
        xc, lc = inp
        logits = _chunk_logits(xc, w)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(
            logits, lc[..., None].astype(jnp.int32), axis=-1
        )[..., 0]
        return acc + jnp.sum(lse - picked), lse

    total, lses = jax.lax.scan(f, jnp.float32(0.0), (xr, lr))
    loss = total / (B * S)
    return loss, (x, w, labels, lses)


def _fused_ce_bwd(n, res, g):
    x, w, labels, lses = res
    B, S, H = x.shape
    V = w.shape[0]
    c = S // n
    xr = jnp.moveaxis(x.reshape(B, n, c, H), 1, 0)
    lr = jnp.moveaxis(labels.reshape(B, n, c), 1, 0)
    scale = (g / (B * S)).astype(jnp.float32)

    def b(dw, inp):
        xc, lc, lse = inp
        logits = _chunk_logits(xc, w)
        p = jnp.exp(logits - lse[..., None])              # stable: logits<=lse
        onehot = (
            jax.lax.broadcasted_iota(jnp.int32, p.shape, 2)
            == lc[..., None].astype(jnp.int32)
        )
        ds = (p - onehot.astype(p.dtype)) * scale          # [B, c, V] f32
        dxc = jax.lax.dot_general(                         # ds @ W -> [B, c, H]
            ds.astype(w.dtype), w, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dw_c = jax.lax.dot_general(                        # ds^T @ x -> [V, H]
            ds.astype(xc.dtype), xc,
            (((0, 1), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dw + dw_c, dxc.astype(x.dtype)

    dw, dxs = jax.lax.scan(b, jnp.zeros((V, H), jnp.float32), (xr, lr, lses))
    dx = jnp.moveaxis(dxs, 0, 1).reshape(B, S, H)
    dlabels = np.zeros(labels.shape, jax.dtypes.float0)    # int input: no grad
    return dx, dw.astype(w.dtype), dlabels


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def fused_linear_cross_entropy(x, weight, labels, n_chunks=None):
    """Mean token cross-entropy of `x @ weight.T` against `labels`, computed
    in sequence chunks so the full [B, S, V] logits never exist in HBM.

    x: [B, S, H]; weight: [V, H] (e.g. a tied wte); labels: [B, S] int.
    n_chunks: sequence chunks (None = auto, ~256 MB f32 logits per chunk).
    Exact same value/grads as the unfused logsumexp CE (tests assert)."""
    B, S, H = x.shape
    V = weight.shape[0]
    n = _pick_chunks(B, S, V, n_chunks)
    return _fused_ce(x, weight, labels.astype(jnp.int32), n)
