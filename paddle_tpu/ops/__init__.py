"""Functional op library (the PHI-kernel-library role, SURVEY.md §2.1).

Every op is a thin differentiable wrapper over jnp/lax — XLA is the kernel
library; this package is the registry + dispatch layer
(reference: paddle/phi/kernels + paddle/phi/api).
"""
from . import (  # noqa: F401
    activation,
    common_nn,
    conv_pool,
    creation,
    linalg,
    logic,
    loss_ops,
    manipulation,
    math,
    norm_ops,
    search,
)
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
