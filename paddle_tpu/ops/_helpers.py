"""Op application helpers: bridge public Tensor API → autograd.apply → jnp.

Reference parity: the role of the generated `*_ad_func` wrappers
(/root/reference/paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:192)
— convert inputs, dispatch, record autograd — done generically instead of via
per-op codegen because jax.vjp supplies every backward.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import autograd
from ..core.tensor import Tensor


def T(x, dtype=None):
    """Coerce anything tensor-like into a Tensor (no copy for Tensors)."""
    if isinstance(x, Tensor):
        return x
    t = Tensor(x, dtype=dtype)
    return t


def op(fn, *inputs, name=None):
    """Differentiable single-output op over Tensor inputs."""
    tensors = tuple(T(x) for x in inputs)
    out, node = autograd.apply(fn, *tensors, name=name)
    return Tensor._from_op(out, node)


def op_multi(fn, *inputs, name=None):
    """Differentiable multi-output op; returns tuple of Tensors sharing a node."""
    tensors = tuple(T(x) for x in inputs)
    out, node = autograd.apply(fn, *tensors, name=name)
    return tuple(Tensor._from_op(o, node, i) for i, o in enumerate(out))


def nondiff(fn, *inputs, name=None):
    """Non-differentiable op (integer/bool outputs): never recorded on tape."""
    arrays = tuple(T(x)._array for x in inputs)
    out = fn(*arrays)
    if isinstance(out, (tuple, list)):
        return tuple(Tensor._from_op(o) for o in out)
    return Tensor._from_op(out)


def promote_binary(x, y):
    """Paddle-flavored binary promotion: python scalars adopt tensor dtype."""
    xs = not isinstance(x, (Tensor, jnp.ndarray, np.ndarray))
    ys = not isinstance(y, (Tensor, jnp.ndarray, np.ndarray))
    if xs and not ys:
        yt = T(y)
        return T(np.asarray(x).astype(_scalar_target(np.asarray(x), yt.dtype))), yt
    if ys and not xs:
        xt = T(x)
        return xt, T(np.asarray(y).astype(_scalar_target(np.asarray(y), xt.dtype)))
    return T(x), T(y)


def _scalar_target(scalar, tensor_dtype):
    # float scalar with int tensor promotes to default float; else tensor dtype
    if scalar.dtype.kind == "f" and np.dtype(tensor_dtype).kind in "iub":
        return np.float32
    return tensor_dtype


def binop(fn, x, y, name=None):
    xt, yt = promote_binary(x, y)
    out, node = autograd.apply(fn, xt, yt, name=name)
    return Tensor._from_op(out, node)


def axes_arg(axis):
    """Normalize paddle axis arguments (int | list | tuple | None | Tensor)."""
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def int_or_list(v):
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    return int(v)
