"""Shape/layout manipulation ops.

Reference parity: python/paddle/tensor/manipulation.py in /root/reference
(reshape, transpose, squeeze, concat, split, gather, scatter, tile, expand,
flip, roll, unique, pad, ...). All static-shape friendly — sizes resolved in
Python so XLA sees fixed shapes (SURVEY.md §7 hard part 2).
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd
from ..core.tensor import Tensor
from ._helpers import T, nondiff, op, op_multi


def _resolve_shape(shape, x):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    out = []
    for s in shape:
        if isinstance(s, Tensor):
            out.append(int(s.item()))
        else:
            try:
                out.append(int(s))
            except Exception:
                # symbolic dim (jax.export shape polymorphism) passes through
                out.append(s)
    return out


def reshape(x, shape, name=None):
    shp = _resolve_shape(shape, x)
    return op(lambda a: jnp.reshape(a, shp), T(x), name="reshape")


def reshape_(x, shape, name=None):
    t = reshape(x, shape)
    x._array, x._node, x._out_index = t._array, t._node, t._out_index
    x.stop_gradient = t.stop_gradient
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    xt = T(x)
    nd = xt.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    shape = xt.shape[:s] + [-1] + xt.shape[e + 1 :]
    return reshape(xt, shape)


def transpose(x, perm=None, name=None):
    p = None if perm is None else tuple(int(i) for i in perm)
    return op(lambda a: jnp.transpose(a, p), T(x), name="transpose")


def t(x, name=None):
    xt = T(x)
    if xt.ndim < 2:
        return xt.clone()
    return transpose(xt, list(range(xt.ndim - 2)) + [xt.ndim - 1, xt.ndim - 2])


def moveaxis(x, source, destination, name=None):
    return op(lambda a: jnp.moveaxis(a, source, destination), T(x), name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return op(lambda a: jnp.swapaxes(a, axis0, axis1), T(x), name="swapaxes")


transpose_ = transpose


def squeeze(x, axis=None, name=None):
    xt = T(x)
    if axis is None:
        ax = None
    else:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a % xt.ndim for a in axes if xt.shape[a % xt.ndim] == 1)
    return op(lambda a: jnp.squeeze(a, ax), xt, name="squeeze")


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a.item()) if isinstance(a, Tensor) else int(a) for a in axes]
    return op(lambda a: jnp.expand_dims(a, tuple(axes)), T(x), name="unsqueeze")


unsqueeze_ = unsqueeze
squeeze_ = squeeze


def concat(x, axis=0, name=None):
    tensors = tuple(T(t) for t in x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    out, node = autograd.apply(
        lambda *arrs: jnp.concatenate(arrs, axis=int(axis)), *tensors, name="concat"
    )
    return Tensor._from_op(out, node)


def stack(x, axis=0, name=None):
    tensors = tuple(T(t) for t in x)
    out, node = autograd.apply(
        lambda *arrs: jnp.stack(arrs, axis=int(axis)), *tensors, name="stack"
    )
    return Tensor._from_op(out, node)


def split(x, num_or_sections, axis=0, name=None):
    xt = T(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    ax = ax % xt.ndim
    dim = xt.shape[ax]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        if builtins.any(s == -1 for s in sizes):
            rem = dim - builtins.sum(s for s in sizes if s != -1)
            sizes = [rem if s == -1 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()
    outs = []
    for off, sz in zip(offsets, sizes):
        outs.append(
            op(
                lambda a, off=off, sz=sz: jax.lax.slice_in_dim(a, off, off + sz, axis=ax),
                xt,
                name="split",
            )
        )
    return outs


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    xt = T(x)
    ax = axis % xt.ndim
    return [squeeze(s, ax) for s in split(xt, xt.shape[ax], ax)]


def tile(x, repeat_times, name=None):
    reps = _resolve_shape(repeat_times, x)
    return op(lambda a: jnp.tile(a, reps), T(x), name="tile")


def expand(x, shape, name=None):
    xt = T(x)
    shp = _resolve_shape(shape, x)
    shp = [xt.shape[i - (len(shp) - xt.ndim)] if s in (-1,) else s for i, s in enumerate(shp)]
    return op(lambda a: jnp.broadcast_to(a, shp), xt, name="expand")


def expand_as(x, y, name=None):
    return expand(x, T(y).shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    tensors = tuple(T(t) for t in inputs)
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in tensors])
    return [expand(t, list(shape)) for t in tensors]


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def flip(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return op(lambda a: jnp.flip(a, ax), T(x), name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return op(lambda a: jnp.rot90(a, k, axes), T(x), name="rot90")


def roll(x, shifts, axis=None, name=None):
    return op(lambda a: jnp.roll(a, shifts, axis), T(x), name="roll")


def slice(x, axes, starts, ends, name=None):
    xt = T(x)
    idx = [builtins.slice(None)] * xt.ndim
    for ax, s, e in zip(axes, starts, ends):
        s = int(s.item()) if isinstance(s, Tensor) else int(s)
        e = int(e.item()) if isinstance(e, Tensor) else int(e)
        idx[ax] = builtins.slice(s, e)
    idx = tuple(idx)
    return op(lambda a: a[idx], xt, name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    xt = T(x)
    idx = [builtins.slice(None)] * xt.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(int(s), int(e), int(st))
    idx = tuple(idx)
    return op(lambda a: a[idx], xt, name="strided_slice")


def crop(x, shape=None, offsets=None, name=None):
    xt = T(x)
    shp = _resolve_shape(shape, x)
    offs = offsets or [0] * xt.ndim
    offs = [int(o.item()) if isinstance(o, Tensor) else int(o) for o in offs]
    shp = [xt.shape[i] - offs[i] if s == -1 else s for i, s in enumerate(shp)]
    idx = tuple(builtins.slice(o, o + s) for o, s in zip(offs, shp))
    return op(lambda a: a[idx], xt, name="crop")


# ---- gather / scatter -----------------------------------------------------

def gather(x, index, axis=0, name=None):
    xt, it = T(x), T(index)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    idx = it._array.reshape(-1)
    return op(lambda a: jnp.take(a, idx, axis=ax), xt, name="gather")


def gather_nd(x, index, name=None):
    xt, it = T(x), T(index)
    idx = it._array

    def f(a):
        return a[tuple(jnp.moveaxis(idx, -1, 0))]

    return op(f, xt, name="gather_nd")


def take(x, index, mode="raise", name=None):
    xt, it = T(x), T(index)
    idx = it._array
    m = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]
    return op(lambda a: jnp.take(a.reshape(-1), idx, mode=m), xt, name="take")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    xt, it = T(arr), T(indices)
    idx = it._array
    return op(lambda a: jnp.take_along_axis(a, idx, axis=axis), xt, name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    xt, it = T(arr), T(indices)
    vt = T(values)
    idx = it._array

    def f(a, v):
        v = jnp.broadcast_to(v.astype(a.dtype), idx.shape)
        if reduce == "assign":
            return jnp.put_along_axis(a, idx, v, axis=axis, inplace=False)
        ii = [jnp.arange(s).reshape([-1 if i == d else 1 for i in range(a.ndim)])
              for d, s in enumerate(idx.shape)]
        ii[axis] = idx
        if reduce == "add":
            return a.at[tuple(ii)].add(v)
        if reduce in ("multiply", "mul"):
            return a.at[tuple(ii)].multiply(v)
        raise ValueError(reduce)

    out, node = autograd.apply(f, xt, vt, name="put_along_axis")
    return Tensor._from_op(out, node)


def scatter(x, index, updates, overwrite=True, name=None):
    xt, it, ut = T(x), T(index), T(updates)
    idx = it._array.reshape(-1)

    def f(a, u):
        if overwrite:
            return a.at[idx].set(u.astype(a.dtype))
        return a.at[idx].add(u.astype(a.dtype))

    out, node = autograd.apply(f, xt, ut, name="scatter")
    return Tensor._from_op(out, node)


def scatter_nd_add(x, index, updates, name=None):
    xt, it, ut = T(x), T(index), T(updates)
    idx = it._array

    def f(a, u):
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u.astype(a.dtype))

    out, node = autograd.apply(f, xt, ut, name="scatter_nd_add")
    return Tensor._from_op(out, node)


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros

    z = zeros(shape, dtype=T(updates).dtype)
    return scatter_nd_add(z, index, updates)


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index, name=None):
    xt, it = T(x), T(index)
    idx = it._array

    def f(a):
        rows = jnp.arange(a.shape[0])[:, None]
        return a[rows, idx]

    return op(f, xt, name="index_sample")


def index_add(x, index, axis, value, name=None):
    xt, it, vt = T(x), T(index), T(value)
    idx = it._array.reshape(-1)

    def f(a, v):
        am = jnp.moveaxis(a, axis, 0)
        vm = jnp.moveaxis(v.astype(a.dtype), axis, 0)
        return jnp.moveaxis(am.at[idx].add(vm), 0, axis)

    out, node = autograd.apply(f, xt, vt, name="index_add")
    return Tensor._from_op(out, node)


def index_put(x, indices, value, accumulate=False, name=None):
    xt = T(x)
    vt = T(value)
    idx = tuple(T(i)._array for i in indices)

    def f(a, v):
        if accumulate:
            return a.at[idx].add(v.astype(a.dtype))
        return a.at[idx].set(jnp.broadcast_to(v.astype(a.dtype), a[idx].shape))

    out, node = autograd.apply(f, xt, vt, name="index_put")
    return Tensor._from_op(out, node)


def masked_select(x, mask, name=None):
    xt, mt = T(x), T(mask)
    # dynamic output shape: resolve eagerly (not jittable — documented)
    out = xt._array[np.asarray(mt._array)]
    return Tensor._from_op(out)


def masked_fill(x, mask, value, name=None):
    xt, mt = T(x), T(mask)
    m = mt._array
    v = value._array if isinstance(value, Tensor) else value
    return op(lambda a: jnp.where(m, jnp.asarray(v, a.dtype), a), xt, name="masked_fill")


def where(condition, x=None, y=None, name=None):
    ct = T(condition)
    if x is None and y is None:
        return nonzero(ct, as_tuple=True)
    xt, yt = T(x), T(y)
    cond = ct._array
    out, node = autograd.apply(
        lambda a, b: jnp.where(cond, a, b), xt, yt, name="where"
    )
    return Tensor._from_op(out, node)


def nonzero(x, as_tuple=False, name=None):
    xt = T(x)
    nz = np.nonzero(np.asarray(xt._array))
    if as_tuple:
        return tuple(Tensor._from_op(jnp.asarray(i)) for i in nz)
    return Tensor._from_op(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, name=None):
    xt = T(x)
    res = np.unique(
        np.asarray(xt._array),
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if not isinstance(res, tuple):
        return Tensor._from_op(jnp.asarray(res))
    return tuple(Tensor._from_op(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    xt = np.asarray(T(x)._array)
    if axis is not None:
        raise NotImplementedError
    flat = xt.reshape(-1)
    keep = np.concatenate([[True], flat[1:] != flat[:-1]])
    out = flat[keep]
    rets = [Tensor._from_op(jnp.asarray(out))]
    if return_inverse:
        rets.append(Tensor._from_op(jnp.asarray(np.cumsum(keep) - 1)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.concatenate([idx, [flat.size]]))
        rets.append(Tensor._from_op(jnp.asarray(counts)))
    return rets[0] if len(rets) == 1 else tuple(rets)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        repeats = repeats._array
    return op(lambda a: jnp.repeat(a, repeats, axis=axis), T(x), name="repeat_interleave")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    xt = T(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = xt.ndim
    if len(pad) == 2 * nd:
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle/torch convention: pair i applies to spatial dim counted
        # from the LAST backward — [left, right, top, bottom] pads W with
        # (left, right) and H with (top, bottom)
        widths = [(0, 0)] * nd
        npairs = len(pad) // 2
        if data_format.endswith("C") and nd >= 3:  # NHWC / NLC / NDHWC
            dims = [nd - 2 - i for i in range(npairs)]  # W, H, D...
        else:  # NCHW / NCL / NCDHW
            dims = [nd - 1 - i for i in range(npairs)]
        for i, d in enumerate(dims):
            widths[d] = (pad[2 * i], pad[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    kw = {"constant_values": value} if jmode == "constant" else {}
    return op(lambda a: jnp.pad(a, widths, mode=jmode, **kw), xt, name="pad")


def cast(x, dtype):
    return T(x).astype(dtype)


def tensordot(x, y, axes=2, name=None):
    from ._helpers import binop

    if isinstance(axes, Tensor):
        axes = axes.tolist()
    return binop(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y, name="tensordot")


def as_strided(x, shape, stride, offset=0, name=None):
    raise NotImplementedError("as_strided is not supported on TPU (no strided views)")


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return T(x).astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, T(other).shape)


def atleast_1d(*inputs, name=None):
    outs = [reshape(T(x), [-1]) if T(x).ndim == 0 else T(x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = []
    for x in inputs:
        xt = T(x)
        outs.append(op(jnp.atleast_2d, xt, name="atleast_2d"))
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = []
    for x in inputs:
        xt = T(x)
        outs.append(op(jnp.atleast_3d, xt, name="atleast_3d"))
    return outs[0] if len(outs) == 1 else outs


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1, name=None):
    it = T(input)
    shard_size = (index_num + nshards - 1) // nshards

    def f(a):
        in_shard = (a // shard_size) == shard_id
        return jnp.where(in_shard, a % shard_size, ignore_value)

    return nondiff(f, it, name="shard_index")
