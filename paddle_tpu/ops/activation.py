"""Activation functions.

Reference parity: python/paddle/nn/functional/activation.py in /root/reference.
All are jax.nn primitives → XLA fuses them into adjacent matmuls (HBM-bandwidth
friendly; no separate kernels needed on TPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._helpers import T, binop, op


def relu(x, name=None):
    return op(jax.nn.relu, T(x), name="relu")


def relu6(x, name=None):
    return op(jax.nn.relu6, T(x), name="relu6")


def relu_(x, name=None):
    t = relu(x)
    x._array, x._node, x.stop_gradient = t._array, t._node, t.stop_gradient
    return x


def gelu(x, approximate=False, name=None):
    return op(lambda a: jax.nn.gelu(a, approximate=approximate), T(x), name="gelu")


def sigmoid(x, name=None):
    return op(jax.nn.sigmoid, T(x), name="sigmoid")


def tanh(x, name=None):
    return op(jnp.tanh, T(x), name="tanh")


def silu(x, name=None):
    return op(jax.nn.silu, T(x), name="silu")


def swish(x, name=None):
    return silu(x)


def mish(x, name=None):
    return op(lambda a: a * jnp.tanh(jax.nn.softplus(a)), T(x), name="mish")


def leaky_relu(x, negative_slope=0.01, name=None):
    return op(lambda a: jax.nn.leaky_relu(a, negative_slope), T(x), name="leaky_relu")


def elu(x, alpha=1.0, name=None):
    return op(lambda a: jax.nn.elu(a, alpha), T(x), name="elu")


def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    return op(
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), T(x), name="selu"
    )


def celu(x, alpha=1.0, name=None):
    return op(lambda a: jax.nn.celu(a, alpha), T(x), name="celu")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return op(lambda a: jnp.clip(a, min, max), T(x), name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return op(
        lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), T(x), name="hardshrink"
    )


def softshrink(x, threshold=0.5, name=None):
    return op(
        lambda a: jnp.where(
            a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)
        ),
        T(x),
        name="softshrink",
    )


def tanhshrink(x, name=None):
    return op(lambda a: a - jnp.tanh(a), T(x), name="tanhshrink")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return op(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), T(x), name="hardsigmoid")


def hardswish(x, name=None):
    return op(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, T(x), name="hardswish")


def softplus(x, beta=1, threshold=20, name=None):
    return op(
        lambda a: jnp.where(
            beta * a > threshold, a, jax.nn.softplus(beta * a) / beta
        ),
        T(x),
        name="softplus",
    )


def softsign(x, name=None):
    return op(jax.nn.soft_sign, T(x), name="softsign")


def thresholded_relu(x, threshold=1.0, name=None):
    return op(lambda a: jnp.where(a > threshold, a, 0.0), T(x), name="thresholded_relu")


def log_sigmoid(x, name=None):
    return op(jax.nn.log_sigmoid, T(x), name="log_sigmoid")


def maxout(x, groups, axis=1, name=None):
    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        shp = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1 :]
        return jnp.max(a.reshape(shp), axis=ax + 1)

    return op(f, T(x), name="maxout")


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        if data_format == "NCHW":
            shape = (1, -1) + (1,) * (a.ndim - 2)
        else:
            shape = (1,) * (a.ndim - 1) + (-1,)
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return binop(f, x, weight, name="prelu")


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False, name=None):
    from ..core import rng

    if training:
        def f(a):
            r = jax.random.uniform(rng.next_key(), a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, r * a)

        return op(f, T(x), name="rrelu")
    mid = (lower + upper) / 2.0
    return op(lambda a: jnp.where(a >= 0, a, mid * a), T(x), name="rrelu")


def softmax(x, axis=-1, dtype=None, name=None):
    from ..core.dtypes import convert_dtype

    def f(a):
        if dtype is not None:
            a = a.astype(convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)

    return op(f, T(x), name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ..core.dtypes import convert_dtype

    def f(a):
        if dtype is not None:
            a = a.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)

    return op(f, T(x), name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ..core import rng

    def f(a):
        g = jax.random.gumbel(rng.next_key(), a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            y_hard = jax.nn.one_hot(
                jnp.argmax(y, axis=axis), a.shape[axis], axis=axis, dtype=a.dtype
            )
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y

    return op(f, T(x), name="gumbel_softmax")


def glu(x, axis=-1, name=None):
    return op(lambda a: jax.nn.glu(a, axis=axis), T(x), name="glu")
