"""Bind the functional op library onto Tensor as methods + operators.

Reference parity: the method surface installed by eager_method.cc and the
generated monkey-patches in python/paddle/fluid/dygraph/math_op_patch.py.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import activation, creation, linalg, logic, manipulation, math, search
from .common_nn import one_hot
from ._helpers import T


def _method(fn):
    def m(self, *args, **kwargs):
        return fn(self, *args, **kwargs)

    m.__name__ = fn.__name__
    return m


_METHOD_SOURCES = [
    (math, [
        "add", "subtract", "multiply", "divide", "floor_divide", "remainder",
        "mod", "pow", "maximum", "minimum", "fmax", "fmin", "exp", "log",
        "log2", "log10", "log1p", "sqrt", "rsqrt", "square", "abs", "sign",
        "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh",
        "asinh", "acosh", "atanh", "floor", "ceil", "round", "trunc", "frac",
        "reciprocal", "neg", "erf", "erfinv", "lgamma", "digamma", "conj",
        "real", "imag", "angle", "clip", "scale", "lerp", "nan_to_num",
        "isnan", "isinf", "isfinite", "sum", "mean", "prod", "max", "min",
        "amax", "amin", "std", "var", "median", "nanmedian", "nansum",
        "nanmean", "quantile", "logsumexp", "all", "any", "count_nonzero",
        "cumsum", "cumprod", "cummax", "cummin", "logcumsumexp", "inner",
        "outer", "kron", "trace", "diagonal", "diff", "atan2", "heaviside",
        "sigmoid", "deg2rad", "rad2deg", "multiplex", "add_n",
    ]),
    (linalg, [
        "matmul", "mm", "dot", "bmm", "mv", "norm", "dist", "cross",
        "cholesky", "inverse", "det", "slogdet", "svd", "qr", "eigh", "solve",
        "lstsq", "matrix_power", "matrix_rank", "pinv", "cond",
        "triangular_solve",
    ]),
    (manipulation, [
        "reshape", "reshape_", "flatten", "transpose", "t", "moveaxis",
        "swapaxes", "squeeze", "squeeze_", "unsqueeze", "unsqueeze_", "split",
        "chunk", "unbind", "tile", "expand", "expand_as", "broadcast_to",
        "flip", "rot90", "roll", "gather", "gather_nd", "take",
        "take_along_axis", "put_along_axis", "scatter", "scatter_nd_add",
        "index_select", "index_sample", "index_add", "index_put",
        "masked_select", "masked_fill", "where", "nonzero", "unique",
        "unique_consecutive", "repeat_interleave", "pad", "cast",
        "tensordot", "view", "view_as", "slice", "strided_slice",
    ]),
    (logic, [
        "equal", "not_equal", "greater_than", "greater_equal", "less_than",
        "less_equal", "logical_and", "logical_or", "logical_xor",
        "logical_not", "bitwise_and", "bitwise_or", "bitwise_xor",
        "bitwise_not", "isclose", "allclose", "equal_all", "is_empty",
    ]),
    (search, [
        "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "mode",
        "searchsorted", "bucketize", "histogram", "bincount",
    ]),
    (activation, ["relu", "relu_", "softmax", "log_softmax", "gelu"]),
    (creation, ["tril", "triu", "diag", "bernoulli", "multinomial",
                "zeros_like", "ones_like", "full_like"]),
]


def bind():
    for module, names in _METHOD_SOURCES:
        for n in names:
            fn = getattr(module, n)
            if not hasattr(Tensor, n):
                setattr(Tensor, n, _method(fn))
    Tensor.one_hot = _method(one_hot)

    # operators
    Tensor.__add__ = lambda self, o: math.add(self, o)
    Tensor.__radd__ = lambda self, o: math.add(o, self)
    Tensor.__sub__ = lambda self, o: math.subtract(self, o)
    Tensor.__rsub__ = lambda self, o: math.subtract(o, self)
    Tensor.__mul__ = lambda self, o: math.multiply(self, o)
    Tensor.__rmul__ = lambda self, o: math.multiply(o, self)
    Tensor.__truediv__ = lambda self, o: math.divide(self, o)
    Tensor.__rtruediv__ = lambda self, o: math.divide(o, self)
    Tensor.__floordiv__ = lambda self, o: math.floor_divide(self, o)
    Tensor.__rfloordiv__ = lambda self, o: math.floor_divide(o, self)
    Tensor.__mod__ = lambda self, o: math.remainder(self, o)
    Tensor.__pow__ = lambda self, o: math.pow(self, o)
    Tensor.__rpow__ = lambda self, o: math.pow(o, self)
    Tensor.__neg__ = lambda self: math.neg(self)
    Tensor.__abs__ = lambda self: math.abs(self)
    Tensor.__matmul__ = lambda self, o: linalg.matmul(self, o)
    Tensor.__rmatmul__ = lambda self, o: linalg.matmul(o, self)
    Tensor.__eq__ = lambda self, o: logic.equal(self, o)
    Tensor.__ne__ = lambda self, o: logic.not_equal(self, o)
    Tensor.__lt__ = lambda self, o: logic.less_than(self, o)
    Tensor.__le__ = lambda self, o: logic.less_equal(self, o)
    Tensor.__gt__ = lambda self, o: logic.greater_than(self, o)
    Tensor.__ge__ = lambda self, o: logic.greater_equal(self, o)
    import numpy as _np

    def _is_bool(t):
        return _np.dtype(t.dtype).kind == "b"

    Tensor.__and__ = lambda self, o: logic.logical_and(self, o) if _is_bool(self) else logic.bitwise_and(self, o)
    Tensor.__or__ = lambda self, o: logic.logical_or(self, o) if _is_bool(self) else logic.bitwise_or(self, o)
    Tensor.__xor__ = lambda self, o: logic.logical_xor(self, o) if _is_bool(self) else logic.bitwise_xor(self, o)
    Tensor.__invert__ = lambda self: logic.logical_not(self) if _is_bool(self) else logic.bitwise_not(self)

    # in-place aliases used by optimizers / user code
    def add_(self, o):
        self._array = math.add(self.detach(), o)._array
        return self

    def scale_(self, s, bias=0.0):
        self._array = self._array * s + bias
        return self

    def subtract_(self, o):
        self._array = math.subtract(self.detach(), o)._array
        return self

    def multiply_(self, o):
        self._array = math.multiply(self.detach(), o)._array
        return self

    def clip_(self, min=None, max=None):
        self._array = math.clip(self.detach(), min, max)._array
        return self

    Tensor.add_ = add_
    Tensor.scale_ = scale_
    Tensor.subtract_ = subtract_
    Tensor.multiply_ = multiply_
    Tensor.clip_ = clip_
