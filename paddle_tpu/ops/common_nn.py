"""Common nn functional ops: linear, embedding, dropout, one_hot, interpolate,
attention.

Reference parity: python/paddle/nn/functional/{common,input,extension}.py and
flash_attention.py (:20) in /root/reference. Attention routes to the Pallas
flash kernel on TPU (ops/pallas/) with an XLA fallback.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd, rng
from ..core.tensor import Tensor
from ._helpers import T, op


def linear(x, weight, bias=None, name=None):
    # paddle weight layout: [in_features, out_features]
    args = (T(x), T(weight)) + ((T(bias),) if bias is not None else ())

    def f(a, w, *b):
        out = jnp.matmul(a, w.astype(a.dtype))
        if b:
            out = out + b[0].astype(out.dtype)
        return out

    out, node = autograd.apply(f, *args, name="linear")
    return Tensor._from_op(out, node)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    xt, wt = T(x), T(weight)
    idx = xt._array.astype(jnp.int32)

    def f(w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    out, node = autograd.apply(f, wt, name="embedding")
    return Tensor._from_op(out, node)


def one_hot(x, num_classes, name=None):
    xt = T(x)
    return Tensor._from_op(
        jax.nn.one_hot(xt._array.astype(jnp.int32), int(num_classes), dtype=jnp.float32)
    )


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    xt = T(x)
    if not training or p == 0.0:
        return xt.clone() if isinstance(x, Tensor) else xt
    if p == 1.0:
        from .creation import zeros_like

        return zeros_like(xt)
    # the key rides as a real op INPUT (rng.capture_key): under static
    # capture it becomes an RNG slot the executor re-keys per step, so
    # masks vary across steps instead of freezing at capture time
    key = rng.capture_key()

    def f(a, k):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(k, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return op(f, xt, key, name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [2, 3] if data_format == "NCHW" else [1, 2]
    keep_axes = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=keep_axes, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    keep_axes = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=keep_axes, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    xt = T(x)
    if not training or p == 0.0:
        return xt
    key = rng.capture_key()
    alpha = 1.6732632423543772848170429916717
    scale = 1.0507009873554804934193349852946
    alpha_p = -alpha * scale

    def f(a, k):
        keep = jax.random.bernoulli(k, 1.0 - p, a.shape)
        coef_a = (1.0 - p + p * alpha_p**2 * (1.0 - p)) ** -0.5
        coef_b = -coef_a * alpha_p * p
        return coef_a * jnp.where(keep, a, alpha_p) + coef_b

    return op(f, xt, key, name="alpha_dropout")


def interpolate(
    x, size=None, scale_factor=None, mode="nearest", align_corners=False,
    align_mode=0, data_format="NCHW", name=None
):
    xt = T(x)
    channel_last = data_format.endswith("C") and len(data_format) == xt.ndim
    nsp = xt.ndim - 2
    sp_shape = xt.shape[1:-1] if channel_last else xt.shape[2:]
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_sp = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in (size if isinstance(size, (list, tuple)) else [size] * nsp)]
    else:
        if isinstance(scale_factor, (list, tuple)):
            out_sp = [int(s * f) for s, f in zip(sp_shape, scale_factor)]
        else:
            out_sp = [int(s * scale_factor) for s in sp_shape]

    jmode = {
        "nearest": "nearest",
        "bilinear": "linear",
        "linear": "linear",
        "trilinear": "linear",
        "bicubic": "cubic",
        "area": "linear",
    }[mode.lower()]

    def f(a):
        if channel_last:
            full = (a.shape[0],) + tuple(out_sp) + (a.shape[-1],)
        else:
            full = (a.shape[0], a.shape[1]) + tuple(out_sp)
        if jmode == "nearest":
            # jax.image nearest matches paddle's (floor) convention
            return jax.image.resize(a, full, method="nearest")
        if align_corners:
            # manual align-corners linear interp via map_coordinates per spatial dim
            return _resize_align_corners(a, full, channel_last)
        return jax.image.resize(a, full, method=jmode)

    return op(f, xt, name="interpolate")


def _resize_align_corners(a, full, channel_last):
    nsp = a.ndim - 2
    sp_in = a.shape[1:-1] if channel_last else a.shape[2:]
    sp_out = full[1:-1] if channel_last else full[2:]
    coords = []
    for i in range(nsp):
        si, so = sp_in[i], sp_out[i]
        if so == 1:
            c = jnp.zeros((1,))
        else:
            c = jnp.linspace(0.0, si - 1.0, so)
        coords.append(c)
    grid = jnp.meshgrid(*coords, indexing="ij")
    sp_axes = list(range(1, 1 + nsp)) if channel_last else list(range(2, 2 + nsp))

    def interp_single(img):  # img: spatial dims only
        return jax.scipy.ndimage.map_coordinates(img, grid, order=1, mode="nearest")

    moved = jnp.moveaxis(a, sp_axes, list(range(a.ndim - nsp, a.ndim)))
    lead_shape = moved.shape[: a.ndim - nsp]
    flat = moved.reshape((-1,) + tuple(sp_in))
    out = jax.vmap(interp_single)(flat)
    out = out.reshape(lead_shape + tuple(sp_out))
    return jnp.moveaxis(out, list(range(a.ndim - nsp, a.ndim)), sp_axes)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    args = (T(x1), T(x2), T(weight)) + ((T(bias),) if bias is not None else ())

    def f(a, b, w, *bb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bb:
            out = out + bb[0]
        return out

    out, node = autograd.apply(f, *args, name="bilinear")
    return Tensor._from_op(out, node)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    lt = T(label)

    def f(y):
        n = y.shape[-1]
        if prior_dist is not None:
            return (1 - epsilon) * y + epsilon * T(prior_dist)._array
        return (1 - epsilon) * y + epsilon / n

    return op(f, lt, name="label_smooth")


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True, name=None
):
    """Inputs [batch, seq, heads, head_dim] (paddle flash_attention layout)."""
    from .pallas.flash_attention import flash_attention_array

    qt, kt, vt = T(query), T(key), T(value)
    use_drop = dropout_p > 0 and training
    # the mask and the dropout key ride as real op INPUTS (trainable
    # additive biases get gradients; static capture sees data, not baked
    # constants — and the key becomes a per-step-re-keyed RNG slot)
    args = (qt, kt, vt) + ((T(attn_mask),) if attn_mask is not None else ())
    has_mask = attn_mask is not None
    if use_drop:
        args = args + (T(rng.capture_key()),)

    def f(q, k, v, *rest):
        rest = list(rest)
        dk = rest.pop() if use_drop else None
        return flash_attention_array(
            q, k, v, mask=rest[0] if has_mask else None, causal=is_causal,
            dropout_p=dropout_p if training else 0.0, dropout_key=dk,
        )

    out, node = autograd.apply(f, *args, name="sdpa")
    return Tensor._from_op(out, node)


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False, training=True, name=None):
    """Reference python/paddle/nn/functional/flash_attention.py:20 parity."""
    out = scaled_dot_product_attention(
        query, key, value, None, dropout, causal, training
    )
    return out, None


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns, name=None):
    raise NotImplementedError("sparse_attention: use flash/splash attention on TPU")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    xt = T(x)
    ml = int(maxlen) if maxlen is not None else int(np.asarray(xt._array).max())
    from ..core.dtypes import convert_dtype

    def f(a):
        return (jnp.arange(ml) < a[..., None]).astype(convert_dtype(dtype))

    arr = f(xt._array)
    return Tensor._from_op(arr)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    from .conv_pool import unfold as _unfold

    return _unfold(x, kernel_sizes, strides, paddings, dilations)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """col2im: scatter-add unfolded patches back into an image (the inverse
    of unfold; reference fold kernel). x [N, C*kh*kw, L] -> [N, C, H, W]."""
    from ._helpers import int_or_list

    oh, ow = int_or_list(output_sizes) if isinstance(output_sizes, (list, tuple)) else (output_sizes, output_sizes)
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else tuple(kernel_sizes)
    sh, sw = (strides, strides) if isinstance(strides, int) else tuple(strides)
    if isinstance(paddings, int):
        pt = pb = pl = pr = paddings
    else:
        pp = list(paddings)
        if len(pp) == 2:  # [padding_h, padding_w]
            pt = pb = pp[0]
            pl = pr = pp[1]
        elif len(pp) == 4:  # reference order: [top, left, bottom, right]
            pt, pl, pb, pr = pp
        else:
            raise ValueError(f"fold: paddings must be int, 2- or 4-list, got {paddings}")
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else tuple(dilations)
    xt = T(x)
    n, ckk, L = xt.shape
    c = ckk // (kh * kw)
    lh = (oh + pt + pb - (dh * (kh - 1) + 1)) // sh + 1
    lw = (ow + pl + pr - (dw * (kw - 1) + 1)) // sw + 1
    if lh * lw != L:
        raise ValueError(f"fold: L={L} != computed {lh}*{lw} patch grid")

    def f(a):
        p = a.reshape(n, c, kh, kw, lh, lw)
        out = jnp.zeros((n, c, oh + pt + pb, ow + pl + pr), a.dtype)
        for i in range(kh):  # static tap loop: kh*kw scatter-adds
            for j in range(kw):
                ys = i * dh
                xs = j * dw
                out = out.at[
                    :, :, ys:ys + sh * lh:sh, xs:xs + sw * lw:sw
                ].add(p[:, :, i, j])
        return out[:, :, pt:pt + oh, pl:pl + ow]

    return op(f, xt, name="fold")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from .manipulation import pad as _pad

    return _pad(x, pad, mode, value, data_format)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, "constant", 0.0, data_format)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def f(a):
        n, c, h, w = a.shape
        b = n // seg_num
        r = a.reshape(b, seg_num, c, h, w)
        fold_ = int(c * shift_ratio)
        left = jnp.concatenate([r[:, 1:, :fold_], jnp.zeros_like(r[:, :1, :fold_])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(r[:, :1, fold_: 2 * fold_]), r[:, :-1, fold_: 2 * fold_]], axis=1)
        rest = r[:, :, 2 * fold_:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(n, c, h, w)

    return op(f, T(x), name="temporal_shift")
