"""Normalization functional ops.

Reference parity: python/paddle/nn/functional/norm.py in /root/reference;
kernels paddle/phi/kernels/gpu/{batch_norm,layer_norm,group_norm}_kernel.cu.
Running-stat updates are returned functionally (the layer assigns them), so
the same code path works eagerly and under jit tracing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import autograd
from ..core.tensor import Tensor
from ._helpers import T, op


def batch_norm_stats_update(x_arr, axes):
    mean = jnp.mean(x_arr, axis=axes)
    var = jnp.var(x_arr, axis=axes)
    return mean, var


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-5,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    xt = T(x)
    channel_last = data_format.endswith("C") and xt.ndim > 2 and len(data_format) == xt.ndim
    caxis = xt.ndim - 1 if channel_last else (1 if xt.ndim > 1 else 0)
    axes = tuple(i for i in range(xt.ndim) if i != caxis)
    use_batch = training and not use_global_stats

    rm = T(running_mean)
    rv = T(running_var)

    args = [xt]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(T(weight))
    if has_b:
        args.append(T(bias))

    if use_batch:

        def f(a, *wb):
            # stats ACCUMULATE in f32 (a bf16 sum over 1e6+ elements loses
            # ~3 decimal digits) but the elementwise normalize stays in the
            # activation dtype — dtype= on the reductions gets f32 accuracy
            # without materializing an f32 copy of the activations (measured
            # 13% step cost on ResNet-50/v5e for the full-f32 variant)
            shape = [1] * a.ndim
            shape[caxis] = -1
            # each astype below stays virtual inside its reduce fusion — no
            # f32 copy of the activations is ever materialized
            m = jnp.mean(a.astype(jnp.float32), axis=axes)
            v = jnp.mean(
                jnp.square(a.astype(jnp.float32) - m.reshape(shape)), axis=axes
            )
            inv = jax.lax.rsqrt(v + epsilon).astype(a.dtype)
            out = (a - m.astype(a.dtype).reshape(shape)) * inv.reshape(shape)
            i = 0
            if has_w:
                out = out * wb[i].reshape(shape)
                i += 1
            if has_b:
                out = out + wb[i].reshape(shape)
            # stats returned as extra outputs so the forward value (not a
            # leaked tracer) drives the running-stat update, both eagerly and
            # under jit tracing (buffers collected by functional_call)
            return out, jax.lax.stop_gradient(m), jax.lax.stop_gradient(v)

        outs, node = autograd.apply(f, *args, name="batch_norm")
        out, bm, bv = outs
        n = 1
        for ax in axes:
            n *= xt._array.shape[ax]
        factor = n / max(n - 1, 1)

        # the running-stat update is itself an op through the funnel: under
        # static capture it lands in the op log (+ a state-write registration)
        # so Executor.run recomputes AND persists buffers every step — the
        # reference updates BN state inside the main program the same way
        def upd(rm_a, rv_a, bm_a, bv_a):
            new_rm = momentum * rm_a + (1.0 - momentum) * bm_a.astype(rm_a.dtype)
            new_rv = momentum * rv_a + (1.0 - momentum) * (
                bv_a.astype(rv_a.dtype) * factor
            )
            return new_rm, new_rv

        upd_out, _ = autograd.apply(
            upd, rm, rv, Tensor._from_op(bm), Tensor._from_op(bv),
            name="bn_stats_update",
        )
        rm._array, rv._array = upd_out
        autograd.register_state_write(rm, rv)
        return Tensor._from_op(out, node, 0)

    m_arr, v_arr = rm._array, rv._array

    def f(a, *wb):
        shape = [1] * a.ndim
        shape[caxis] = -1
        out = (a - m_arr.reshape(shape).astype(a.dtype)) * jax.lax.rsqrt(
            v_arr.reshape(shape).astype(a.dtype) + epsilon
        )
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out

    out, node = autograd.apply(f, *args, name="batch_norm")
    return Tensor._from_op(out, node)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    xt = T(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    nd = len(normalized_shape)
    axes = tuple(range(xt.ndim - nd, xt.ndim))
    has_w, has_b = weight is not None, bias is not None
    args = [xt] + ([T(weight)] if has_w else []) + ([T(bias)] if has_b else [])

    def f(a, *wb):
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + epsilon)
        i = 0
        if has_w:
            out = out * wb[i].astype(a.dtype)
            i += 1
        if has_b:
            out = out + wb[i].astype(a.dtype)
        return out

    out, node = autograd.apply(f, *args, name="layer_norm")
    return Tensor._from_op(out, node)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    xt = T(x)
    channel_last = data_format.endswith("C") and len(data_format) == xt.ndim
    has_w, has_b = weight is not None, bias is not None
    args = [xt] + ([T(weight)] if has_w else []) + ([T(bias)] if has_b else [])

    def f(a, *wb):
        if channel_last:
            a_ = jnp.moveaxis(a, -1, 1)
        else:
            a_ = a
        n, c = a_.shape[0], a_.shape[1]
        g = num_groups
        r = a_.reshape((n, g, c // g) + a_.shape[2:])
        axes = tuple(range(2, r.ndim))
        m = jnp.mean(r, axis=axes, keepdims=True)
        v = jnp.var(r, axis=axes, keepdims=True)
        r = (r - m) * jax.lax.rsqrt(v + epsilon)
        out = r.reshape(a_.shape)
        shape = (1, c) + (1,) * (a_.ndim - 2)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    out, node = autograd.apply(f, *args, name="group_norm")
    return Tensor._from_op(out, node)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    xt = T(x)
    has_w, has_b = weight is not None, bias is not None
    args = [xt] + ([T(weight)] if has_w else []) + ([T(bias)] if has_b else [])

    def f(a, *wb):
        axes = tuple(range(2, a.ndim))
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + eps)
        shape = (1, a.shape[1]) + (1,) * (a.ndim - 2)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out

    out, node = autograd.apply(f, *args, name="instance_norm")
    return Tensor._from_op(out, node)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def f(a):
        sq = jnp.square(a)
        half = size // 2
        c = a.shape[1]
        pads = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2)
        padded = jnp.pad(sq, pads)
        acc = sum(
            jax.lax.slice_in_dim(padded, i, i + c, axis=1) for i in range(size)
        )
        return a / jnp.power(k + alpha * acc, beta)

    return op(f, T(x), name="local_response_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        n = jnp.power(
            jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=True), 1.0 / p
        )
        return a / jnp.maximum(n, epsilon)

    return op(f, T(x), name="normalize")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (not in reference snapshot; standard for modern LLM stacks)."""
    xt = T(x)
    has_w = weight is not None
    args = [xt] + ([T(weight)] if has_w else [])

    def f(a, *w):
        v = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(v + epsilon)).astype(a.dtype)
        if has_w:
            out = out * w[0].astype(a.dtype)
        return out

    out, node = autograd.apply(f, *args, name="rms_norm")
    return Tensor._from_op(out, node)
