"""Loss functional ops.

Reference parity: python/paddle/nn/functional/loss.py in /root/reference
(cross_entropy, softmax_with_cross_entropy, bce, mse, l1, nll, smooth_l1,
kl_div, margin/cosine losses, ctc subset omitted).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd
from ..core.tensor import Tensor
from ._helpers import T, binop, op


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    it, lt = T(input), T(label)
    has_w = weight is not None
    # label is a real op INPUT (not a closure capture): static-graph capture
    # must see it as data so Executor feeds flow into the replay; jax.vjp
    # hands integer inputs a float0 cotangent, so autograd is unaffected
    args = [it, lt] + ([T(weight)] if has_w else [])

    def f(logits, larr, *w):
        lg = jnp.moveaxis(logits, axis, -1) if axis not in (-1, logits.ndim - 1) else logits
        n_classes = lg.shape[-1]
        logp = jax.nn.log_softmax(lg, axis=-1) if use_softmax else jnp.log(
            jnp.maximum(lg, 1e-30)
        )
        if soft_label:
            lab = larr.astype(logp.dtype)
            if label_smoothing > 0:
                lab = lab * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(lab * logp, axis=-1)
            valid = jnp.ones_like(loss, dtype=bool)
        else:
            lab = larr
            if lab.ndim == logp.ndim:  # trailing 1 dim
                lab = lab.reshape(lab.shape[:-1])
            lab = lab.astype(jnp.int32)
            valid = lab != ignore_index
            safe = jnp.where(valid, lab, 0)
            picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
            if label_smoothing > 0:
                smooth = jnp.mean(logp, axis=-1)
                picked = (1 - label_smoothing) * picked + label_smoothing * smooth
            loss = -jnp.where(valid, picked, 0.0)
            if has_w:
                wv = w[0][safe]
                loss = loss * jnp.where(valid, wv, 0.0)
        if reduction == "mean":
            if has_w and not soft_label:
                denom = jnp.sum(jnp.where(valid, w[0][jnp.where(valid, lab, 0)], 0.0))
                return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
            denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
            return jnp.sum(loss) / denom
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    out, node = autograd.apply(f, *args, name="cross_entropy")
    return Tensor._from_op(out, node)


def softmax_with_cross_entropy(
    logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True,
    return_softmax=False, axis=-1, name=None
):
    loss = cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        reduction="none", axis=axis,
    )
    lt = T(label)
    if not soft_label and lt.ndim == T(logits).ndim:
        from .manipulation import unsqueeze

        loss = unsqueeze(loss, axis)
    if return_softmax:
        from .activation import softmax as _softmax

        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    return _nll(input, label, weight, ignore_index, reduction)


def _nll(input, label, weight, ignore_index, reduction):
    it, lt = T(input), T(label)
    has_w = weight is not None
    args = [it, lt] + ([T(weight)] if has_w else [])

    def f(logp, larr, *w):
        larr = larr.astype(jnp.int32)
        valid = larr != ignore_index
        safe = jnp.where(valid, larr, 0)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        loss = -jnp.where(valid, picked, 0.0)
        if has_w:
            loss = loss * w[0][safe]
        if reduction == "mean":
            denom = jnp.sum(w[0][safe] * valid) if has_w else jnp.maximum(jnp.sum(valid), 1)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    out, node = autograd.apply(f, *args, name="nll_loss")
    return Tensor._from_op(out, node)


def mse_loss(input, label, reduction="mean", name=None):
    return binop(
        lambda a, b: _reduce(jnp.square(a - b), reduction), input, label, name="mse_loss"
    )


def l1_loss(input, label, reduction="mean", name=None):
    return binop(
        lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label, name="l1_loss"
    )


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return binop(f, input, label, name="smooth_l1_loss")


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    return smooth_l1_loss(input, label, reduction, delta)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    it, lt = T(input), T(label)
    has_w = weight is not None
    args = [it, lt] + ([T(weight)] if has_w else [])

    def f(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if has_w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    out, node = autograd.apply(f, *args, name="bce")
    return Tensor._from_op(out, node)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    it, lt = T(logit), T(label)
    has_w = weight is not None
    has_pw = pos_weight is not None
    args = [it, lt] + ([T(weight)] if has_w else []) + ([T(pos_weight)] if has_pw else [])

    def f(x, y, *rest):
        i = 0
        w = rest[i] if has_w else None
        if has_w:
            i += 1
        pw = rest[i] if has_pw else None
        max_val = jnp.maximum(-x, 0.0)
        if has_pw:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * x + log_w * (jnp.log(jnp.exp(-max_val) + jnp.exp(-x - max_val)) + max_val)
        else:
            loss = (1 - y) * x + max_val + jnp.log(jnp.exp(-max_val) + jnp.exp(-x - max_val))
        if has_w:
            loss = loss * w
        return _reduce(loss, reduction)

    out, node = autograd.apply(f, *args, name="bce_with_logits")
    return Tensor._from_op(out, node)


def kl_div(input, label, reduction="mean", name=None):
    def f(logp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return binop(f, input, label, name="kl_div")


def log_loss(input, label, epsilon=1e-4, name=None):
    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)

    return binop(f, input, label, name="log_loss")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    it, ot, lt = T(input), T(other), T(label)

    def f(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)

    out, node = autograd.apply(f, it, ot, lt, name="margin_ranking_loss")
    return Tensor._from_op(out, node)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)

    return binop(f, input, label, name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean", name=None):
    i1, i2, lt = T(input1), T(input2), T(label)

    def f(a, b, y):
        cos = jnp.sum(a * b, -1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    out, node = autograd.apply(f, i1, i2, lt, name="cosine_embedding_loss")
    return Tensor._from_op(out, node)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    it, pt, nt = T(input), T(positive), T(negative)

    def f(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.power(jnp.abs(a - pos) + epsilon, p), -1), 1 / p)
        dn = jnp.power(jnp.sum(jnp.power(jnp.abs(a - neg) + epsilon, p), -1), 1 / p)
        if swap:
            dpn = jnp.power(jnp.sum(jnp.power(jnp.abs(pos - neg) + epsilon, p), -1), 1 / p)
            dn = jnp.minimum(dn, dpn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    out, node = autograd.apply(f, it, pt, nt, name="triplet_margin_loss")
    return Tensor._from_op(out, node)


def square_error_cost(input, label, name=None):
    return binop(lambda a, b: jnp.square(a - b), input, label, name="square_error_cost")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    lt = T(logit)
    yt = T(label)
    args = (lt, yt) + ((T(normalizer),) if normalizer is not None else ())

    def f(x, y, *norm):
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if norm:
            loss = loss / norm[0]
        return _reduce(loss, reduction)

    out, node = autograd.apply(f, *args, name="sigmoid_focal_loss")
    return Tensor._from_op(out, node)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        dot_ = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot_ / jnp.maximum(na * nb, eps)

    return binop(f, x1, x2, name="cosine_similarity")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC loss via the forward (alpha) recursion as ONE lax.scan over time
    (reference phi warpctc kernel semantics; log-space, batched with masks
    so every sample shares the compiled loop regardless of its lengths).

    log_probs [T, N, C] UNSCALED logits (softmax integrated, the\n    warpctc contract); labels [N, L]; input_lengths /
    label_lengths [N]. reduction 'mean' divides each loss by its label
    length then averages (reference behavior)."""
    lp_t, lab_t = T(log_probs), T(labels)
    il_t, ll_t = T(input_lengths), T(label_lengths)

    def f(logits_in, lab, in_len, lab_len):
        # reference warpctc contract: UNSCALED logits in, softmax integrated
        lp = jax.nn.log_softmax(logits_in, axis=-1)
        Tm, N, C = lp.shape
        Lmax = lab.shape[1]
        S = 2 * Lmax + 1
        NEG = -1e30
        lab = lab.astype(jnp.int32)
        # extended label sequence: blank, l1, blank, l2, ..., blank
        ext = jnp.full((N, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        prev2 = jnp.concatenate(
            [jnp.full((N, 2), -1, jnp.int32), ext[:, :-2]], axis=1
        )
        skip_ok = (ext != blank) & (ext != prev2)  # [N, S]
        s_idx = jnp.arange(S)[None, :]
        valid_s = s_idx < (2 * lab_len[:, None] + 1)

        def emit(lp_frame):  # [N, C] -> [N, S] log prob of each ext symbol
            return jnp.take_along_axis(lp_frame, ext, axis=1)

        alpha0 = jnp.full((N, S), NEG)
        e0 = emit(lp[0])
        alpha0 = alpha0.at[:, 0].set(e0[:, 0])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0, e0[:, 1], NEG)
        )

        def logsum3(a, b, c):
            m = jnp.maximum(jnp.maximum(a, b), c)
            m_safe = jnp.maximum(m, NEG)
            return m_safe + jnp.log(
                jnp.exp(a - m_safe) + jnp.exp(b - m_safe) + jnp.exp(c - m_safe)
            )

        def step(alpha, lp_frame):
            a1 = alpha
            a2 = jnp.concatenate([jnp.full((N, 1), NEG), alpha[:, :-1]], axis=1)
            a3 = jnp.concatenate([jnp.full((N, 2), NEG), alpha[:, :-2]], axis=1)
            a3 = jnp.where(skip_ok, a3, NEG)
            new = logsum3(a1, a2, a3) + emit(lp_frame)
            new = jnp.where(valid_s, new, NEG)
            return new, new

        _, alphas = jax.lax.scan(step, alpha0, lp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, N, S]
        # per-sample final frame t = input_len - 1
        t_last = jnp.clip(in_len.astype(jnp.int32) - 1, 0, Tm - 1)
        at = alphas[t_last, jnp.arange(N)]  # [N, S]
        e_blank = jnp.take_along_axis(at, (2 * lab_len[:, None]).astype(jnp.int32), 1)[:, 0]
        e_label = jnp.take_along_axis(
            at, jnp.clip(2 * lab_len[:, None] - 1, 0, S - 1).astype(jnp.int32), 1
        )[:, 0]
        e_label = jnp.where(lab_len > 0, e_label, NEG)
        return -jnp.logaddexp(e_blank, e_label)  # per-sample [N]

    def g(logits_in, lab, in_len, lab_len):
        core = lambda lg: f(lg, lab, in_len, lab_len)
        if norm_by_times:
            # reference warpctc: norm_by_times scales only the GRADIENT by
            # 1/T per sample; the forward loss value stays unscaled
            @jax.custom_vjp
            def nbt(lg):
                return core(lg)

            def nbt_fwd(lg):
                out, vjp_fn = jax.vjp(core, lg)
                return out, vjp_fn

            def nbt_bwd(vjp_fn, ct):
                scaled = ct / jnp.maximum(in_len.astype(ct.dtype), 1.0)
                return vjp_fn(scaled)

            nbt.defvjp(nbt_fwd, nbt_bwd)
            loss = nbt(logits_in)
        else:
            loss = core(logits_in)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1.0))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return op(g, lp_t, lab_t, il_t, ll_t, name="ctc_loss")
