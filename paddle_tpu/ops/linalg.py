"""Linear algebra ops.

Reference parity: python/paddle/tensor/linalg.py in /root/reference (matmul at
:233, norm, decomposition suite). matmul is the MXU hot path: kept as a single
dot_general so XLA tiles it onto the systolic array; bf16 inputs stay bf16.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ._helpers import T, binop, nondiff, op, op_multi


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return binop(f, x, y, name="matmul")


mm = matmul


def dot(x, y, name=None):
    def f(a, b):
        return jnp.sum(a * b, axis=-1)

    return binop(f, x, y, name="dot")


def bmm(x, y, name=None):
    return binop(jnp.matmul, x, y, name="bmm")


def mv(x, vec, name=None):
    return binop(jnp.matmul, x, vec, name="mv")


def matmul_with_flatten(x, y, x_num_col_dims=1, name=None):
    def f(a, b):
        lead = int(np.prod(a.shape[:x_num_col_dims])) if x_num_col_dims else 1
        return jnp.matmul(a.reshape(lead, -1), b.reshape(b.shape[0], -1) if b.ndim > 2 else b)

    return binop(f, x, y, name="mul")


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def f(a):
        if p == "fro" and (axis is None or isinstance(axis, (list, tuple))):
            ax = tuple(axis) if axis is not None else None
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=keepdim))
        if p in ("nuc",):
            return jnp.sum(jnp.linalg.svd(a, compute_uv=False), axis=-1)
        pv = float(p)
        ax = axis if not isinstance(axis, (list, tuple)) else tuple(axis)
        if pv == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if pv == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if pv == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.power(
            jnp.sum(jnp.power(jnp.abs(a), pv), axis=ax, keepdims=keepdim), 1.0 / pv
        )

    return op(f, T(x), name="norm")


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p, axis, keepdim)


def dist(x, y, p=2, name=None):
    return norm(binop(jnp.subtract, x, y, name="sub"), p=p)


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)

    return binop(f, x, y, name="cross")


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L

    return op(f, T(x), name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)

    return binop(f, x, y, name="cholesky_solve")


def inverse(x, name=None):
    return op(jnp.linalg.inv, T(x), name="inverse")


inv = inverse


def det(x, name=None):
    return op(jnp.linalg.det, T(x), name="det")


def slogdet(x, name=None):
    def f(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])

    return op(f, T(x), name="slogdet")


def svd(x, full_matrices=False, name=None):
    return op_multi(
        lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
        T(x),
        name="svd",
    )


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    u, s, vh = svd(x)
    from .manipulation import slice as slice_op

    return u, s, vh


def qr(x, mode="reduced", name=None):
    return op_multi(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), T(x), name="qr")


def eig(x, name=None):
    a = np.asarray(T(x)._array)
    w, v = np.linalg.eig(a)
    return Tensor._from_op(jnp.asarray(w)), Tensor._from_op(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    return op_multi(lambda a: tuple(jnp.linalg.eigh(a, symmetrize_input=True)), T(x), name="eigh")


def eigvals(x, name=None):
    a = np.asarray(T(x)._array)
    return Tensor._from_op(jnp.asarray(np.linalg.eigvals(a)))


def eigvalsh(x, UPLO="L", name=None):
    return op(lambda a: jnp.linalg.eigvalsh(a), T(x), name="eigvalsh")


def solve(x, y, name=None):
    return binop(jnp.linalg.solve, x, y, name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return binop(f, x, y, name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    def f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol

    return binop(f, x, y, name="lstsq")


def matrix_power(x, n, name=None):
    return op(lambda a: jnp.linalg.matrix_power(a, int(n)), T(x), name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return nondiff(
        lambda a: jnp.linalg.matrix_rank(a, rtol=tol), T(x), name="matrix_rank"
    )


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return op(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), T(x), name="pinv")


def multi_dot(tensors, name=None):
    from ..core import autograd

    ts = tuple(T(t) for t in tensors)
    out, node = autograd.apply(
        lambda *arrs: jnp.linalg.multi_dot(arrs), *ts, name="multi_dot"
    )
    return Tensor._from_op(out, node)


def cond(x, p=None, name=None):
    return nondiff(lambda a: jnp.linalg.cond(a, p=p), T(x), name="cond")


def lu(x, pivot=True, get_infos=False, name=None):
    def f(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv

    xt = T(x)
    lu_, piv = jax.scipy.linalg.lu_factor(xt._array)
    outs = (
        Tensor._from_op(lu_),
        Tensor._from_op((piv + 1).astype(np.int32)),
    )
    if get_infos:
        return outs + (Tensor._from_op(jnp.zeros((), np.int32)),)
    return outs


def corrcoef(x, rowvar=True, name=None):
    return op(lambda a: jnp.corrcoef(a, rowvar=rowvar), T(x), name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return op(
        lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), T(x), name="cov"
    )


def householder_product(x, tau, name=None):
    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(n):
            v = jnp.concatenate([jnp.zeros(i, a.dtype), jnp.ones(1, a.dtype), a[i + 1 :, i]])
            q = q - t[i] * (q @ v[:, None]) @ v[None, :]
        return q

    return binop(f, x, tau, name="householder_product")


def einsum(equation, *operands, name=None):
    from ..core import autograd

    ts = tuple(T(t) for t in operands)
    out, node = autograd.apply(
        lambda *arrs: jnp.einsum(equation, *arrs), *ts, name="einsum"
    )
    return Tensor._from_op(out, node)
