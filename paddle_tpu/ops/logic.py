"""Comparison / logical / bitwise ops.

Reference parity: python/paddle/tensor/logic.py in /root/reference.
All outputs are bool/int → non-differentiable by construction.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ._helpers import T, nondiff


def _cmp(jfn, name):
    def f(x, y, name_=None):
        yt = y if isinstance(y, Tensor) else Tensor(np.asarray(y))
        return nondiff(jfn, T(x), yt, name=name)

    f.__name__ = name
    return f


equal = _cmp(jnp.equal, "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")


def logical_not(x, name=None):
    return nondiff(jnp.logical_not, T(x), name="logical_not")


def bitwise_not(x, name=None):
    return nondiff(jnp.bitwise_not, T(x), name="bitwise_not")


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return nondiff(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        T(x),
        T(y),
        name="isclose",
    )


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return nondiff(
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        T(x),
        T(y),
        name="allclose",
    )


def equal_all(x, y, name=None):
    return nondiff(lambda a, b: jnp.array_equal(a, b), T(x), T(y), name="equal_all")


def is_empty(x, name=None):
    return Tensor._from_op(jnp.asarray(T(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
