"""Native data-feed fast path.

Reference parity: the BufferedReader prefetcher + DataFeed batch assembly
(SURVEY.md §2.3 data pipeline). For array-backed datasets this path does
epoch shuffling, batch gather-collate, and bounded prefetch in C++
(csrc/data_feed.cc), handing ready numpy batches to jax.device_put.
"""
from __future__ import annotations

import ctypes
import threading

import numpy as np

from ..utils.cpp_extension import load_native


def shuffle_indices(n, seed):
    lib = load_native()
    idx = np.arange(n, dtype=np.int64)
    lib.df_shuffle_indices(idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n, int(seed) & (2**64 - 1))
    return idx


def gather_collate(base: np.ndarray, indices: np.ndarray, n_threads=4) -> np.ndarray:
    """base: [N, ...]; returns base[indices] via parallel memcpy."""
    lib = load_native()
    base = np.ascontiguousarray(base)
    indices = np.ascontiguousarray(indices, np.int64)
    sample_bytes = base.itemsize * int(np.prod(base.shape[1:], dtype=np.int64))
    out = np.empty((len(indices),) + base.shape[1:], base.dtype)
    lib.df_gather_collate(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        base.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(indices), sample_bytes, n_threads,
    )
    return out


class NativeBatchQueue:
    """Bounded producer/consumer byte queue backed by the C++ ring buffer."""

    def __init__(self, capacity=8):
        self._lib = load_native()
        self._h = self._lib.df_queue_new(capacity)
        self._closed = False

    def push(self, arr: np.ndarray) -> bool:
        arr = np.ascontiguousarray(arr)
        r = self._lib.df_queue_push(
            self._h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), arr.nbytes
        )
        return r == 0

    def pop(self, shape, dtype) -> np.ndarray | None:
        out = np.empty(shape, dtype)
        n = self._lib.df_queue_pop(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), out.nbytes
        )
        if n == 0:
            return None
        if n != out.nbytes:
            raise RuntimeError(f"queue pop size mismatch: {n} vs {out.nbytes}")
        return out

    def close(self):
        if not self._closed:
            self._lib.df_queue_close(self._h)
            self._closed = True

    def __len__(self):
        return int(self._lib.df_queue_size(self._h))

    def __del__(self):
        try:
            self.close()
            self._lib.df_queue_free(self._h)
        except Exception:
            pass


class ArrayDataFeed:
    """High-throughput feed over in-memory arrays (images/labels): C++
    shuffle + collate + prefetch thread. Yields numpy batch tuples."""

    def __init__(self, arrays, batch_size, shuffle=True, drop_last=False, seed=0, prefetch=4, n_threads=4):
        self.arrays = [np.ascontiguousarray(a) for a in arrays]
        self.n = len(self.arrays[0])
        for a in self.arrays[1:]:
            if len(a) != self.n:
                raise ValueError(
                    f"all arrays must share length: {len(a)} != {self.n}"
                )
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.prefetch = prefetch
        self.n_threads = n_threads
        self._epoch = 0

    def __len__(self):
        if self.drop_last:
            return self.n // self.batch_size
        return (self.n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        if self.shuffle:
            idx = shuffle_indices(self.n, self.seed + self._epoch)
        else:
            idx = np.arange(self.n, dtype=np.int64)
        self._epoch += 1
        bs = self.batch_size
        n_batches = len(self)
        fixed_shapes = self.drop_last or self.n % bs == 0
        if fixed_shapes:
            yield from self._iter_native_queue(idx, bs, n_batches)
        else:
            yield from self._iter_python_queue(idx, bs, n_batches)

    def _iter_native_queue(self, idx, bs, n_batches):
        """Fixed-shape batches flow through the C++ ring buffer (the
        BufferedReader double-buffer role)."""
        queues = [NativeBatchQueue(self.prefetch) for _ in self.arrays]
        shapes = [(bs,) + a.shape[1:] for a in self.arrays]
        error = []

        def producer():
            try:
                for b in range(n_batches):
                    sel = idx[b * bs : (b + 1) * bs]
                    for a, q in zip(self.arrays, queues):
                        if not q.push(gather_collate(a, sel, self.n_threads)):
                            return  # consumer closed the queues
            except Exception as e:
                error.append(e)
            finally:
                for q in queues:
                    q.close()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            for _ in range(n_batches):
                batch = tuple(
                    q.pop(shape, a.dtype)
                    for q, shape, a in zip(queues, shapes, self.arrays)
                )
                if any(b is None for b in batch):
                    break
                yield batch
        finally:
            for q in queues:
                q.close()
            t.join(timeout=5)
        if error:
            raise error[0]

    def _iter_python_queue(self, idx, bs, n_batches):
        import queue as pyqueue

        q = pyqueue.Queue(maxsize=self.prefetch)
        SENTINEL = object()

        def producer():
            try:
                for b in range(n_batches):
                    sel = idx[b * bs : (b + 1) * bs]
                    q.put(
                        tuple(gather_collate(a, sel, self.n_threads) for a in self.arrays)
                    )
            except Exception as e:
                q.put(e)
            finally:
                q.put(SENTINEL)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is SENTINEL:
                break
            if isinstance(item, Exception):
                raise item
            yield item
