"""DataLoader: batching + multiprocess workers + device prefetch.

Reference parity: python/paddle/fluid/reader.py:311 (DataLoader),
fluid/dataloader/dataloader_iter.py:162 (single-process) and :370
(multiprocess workers over shared-memory queues), and the C++ BufferedReader
H2D double-buffering (paddle/fluid/operators/reader/buffered_reader.h:48).

TPU design: workers produce numpy batches (multiprocessing.Pool-style worker
loop); a prefetch thread stages the next `prefetch_factor` batches onto the
device with jax.device_put while the current step computes — the
BufferedReader role. Returned batches are framework Tensors.
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as pyqueue
import threading

import numpy as np

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler, DistributedBatchSampler


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch]) for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, np.ndarray):
        arr = np.stack(batch)
    elif isinstance(sample, Tensor):
        arr = np.stack([s.numpy() for s in batch])
    elif isinstance(sample, (int, np.integer)):
        arr = np.asarray(batch, np.int64)
    elif isinstance(sample, (float, np.floating)):
        arr = np.asarray(batch, np.float32)
    else:
        return batch
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


def _to_tensor_tree(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_tensor_tree(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    return obj


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id, num_workers, seed):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset, seed)
    np.random.seed((seed + worker_id) % (2**31))
    while True:
        item = index_queue.get()
        if item is None:
            break
        batch_id, indices = item
        try:
            samples = [dataset[i] for i in indices]
            data_queue.put((batch_id, collate_fn(samples), None))
        except Exception as e:  # propagate worker errors
            data_queue.put((batch_id, None, e))


class DataLoader:
    def __init__(
        self,
        dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn=None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=True,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_buffer_reader = use_buffer_reader
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of IterableDataset loader is unknown")
        return len(self.batch_sampler)

    def _batches_numpy(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        elif self.num_workers == 0:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])
        else:
            yield from self._batches_multiprocess()

    def _batches_multiprocess(self):
        ctx = mp.get_context("fork")
        index_queue = ctx.Queue()
        data_queue = ctx.Queue()
        from ..core.rng import host_generator

        seed = int(host_generator().integers(0, 2**31))
        workers = [
            ctx.Process(
                target=_worker_loop,
                args=(self.dataset, index_queue, data_queue, self.collate_fn, i, self.num_workers, seed),
                daemon=True,
            )
            for i in range(self.num_workers)
        ]
        for w in workers:
            w.start()
        try:
            n_sent = 0
            for batch_id, indices in enumerate(self.batch_sampler):
                index_queue.put((batch_id, indices))
                n_sent += 1
            reorder = {}
            next_id = 0
            for _ in range(n_sent):
                bid, data, err = data_queue.get()
                if err is not None:
                    raise err
                reorder[bid] = data
                while next_id in reorder:
                    yield reorder.pop(next_id)
                    next_id += 1
        finally:
            for _ in workers:
                index_queue.put(None)
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()

    def __iter__(self):
        gen = self._batches_numpy()
        if not self.use_buffer_reader:
            try:
                for b in gen:
                    yield _to_tensor_tree(b)
            finally:
                gen.close()  # triggers worker shutdown in _batches_multiprocess
            return
        # prefetch thread: host->device staging overlaps compute. The stop
        # event + timed puts guarantee the producer exits (and closes the
        # underlying generator, shutting down worker processes) even when the
        # consumer abandons the iterator mid-epoch.
        q = pyqueue.Queue(maxsize=self.prefetch_factor)
        SENTINEL = object()
        stop = threading.Event()

        def producer():
            try:
                for b in gen:
                    item = _to_tensor_tree(b)
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except pyqueue.Full:
                            continue
                    if stop.is_set():
                        return
            except Exception as e:
                if not stop.is_set():
                    try:
                        q.put(e, timeout=1.0)
                    except pyqueue.Full:
                        pass
            finally:
                gen.close()
                while True:
                    try:
                        q.put(SENTINEL, timeout=0.1)
                        break
                    except pyqueue.Full:
                        if stop.is_set():
                            break

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is SENTINEL:
                    break
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()
            t.join(timeout=5)
