"""InMemoryDataset / QueueDataset — the trainer/DataFeed dataset family.

Reference parity: python/paddle/distributed/fleet/dataset/dataset.py
(InMemoryDataset:291 with load_into_memory/local_shuffle/global_shuffle,
QueueDataset:1000 streaming variant) over the C++ MultiSlotDataFeed
(paddle/fluid/framework/data_feed.cc).

TPU-native design: the reference's role for these classes is feeding slot-
formatted text through a C++ pipeline into trainer threads. Here the C++
layer is csrc/data_feed.cc (shuffle + parallel gather-collate) and the
consumer is the compiled train step: parse once into contiguous arrays,
shuffle/batch natively, iterate numpy batches ready for device_put.
Slot format: each line is whitespace-separated `slot_size value...` groups,
one group per declared variable (the reference's MultiSlot text format for
dense slots).
"""
from __future__ import annotations

import numpy as np

from . import native_feed


def _parse_line(toks, var_dims):
    """One slot-text line -> list of per-slot dense value lists (pad or
    truncate each slot to its declared dim)."""
    out = []
    pos = 0
    for dim in var_dims:
        n = int(toks[pos])
        pos += 1
        vals = [float(t) for t in toks[pos:pos + n]]
        pos += n
        if len(vals) < dim:
            vals += [0.0] * (dim - len(vals))
        out.append(vals[:dim])
    return out


class InMemoryDataset:
    """Load slot-text files fully into memory; shuffle natively; iterate
    fixed-size dense batches."""

    def __init__(self):
        self._var_names = []
        self._var_dims = []
        self._batch_size = 1
        self._thread = 1
        self._arrays = None  # list of [N, dim] arrays, one per slot
        self._seed = 0
        self._drop_last = False

    # ---- reference-surface config ----------------------------------------
    def init(self, batch_size=1, thread_num=1, use_var=None, pipe_command=None,
             input_type=0, fs_name="", fs_ugi="", download_cmd=""):
        self._batch_size = int(batch_size)
        self._thread = int(thread_num)
        if use_var:
            self.set_use_var(use_var)

    def set_use_var(self, var_list):
        """var_list: names (str) or objects with .name/.shape; declares the
        slot order and per-slot dense dims."""
        self._var_names = []
        self._var_dims = []
        for v in var_list:
            if isinstance(v, str):
                self._var_names.append(v)
                self._var_dims.append(1)
            else:
                self._var_names.append(getattr(v, "name", str(v)))
                shape = list(getattr(v, "shape", [1]))
                dim = 1
                for d in shape[1:] if len(shape) > 1 else shape:
                    if d and int(d) > 0:
                        dim *= int(d)
                self._var_dims.append(dim)

    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_drop_last(self, drop_last):
        self._drop_last = bool(drop_last)

    def set_thread(self, thread_num):
        self._thread = int(thread_num)

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    # ---- loading / shuffling ----------------------------------------------
    def load_into_memory(self):
        if not self._var_names:
            raise ValueError("call set_use_var before load_into_memory")
        rows = [[] for _ in self._var_names]
        for path in getattr(self, "_filelist", []):
            with open(path) as f:
                for line in f:
                    toks = line.split()
                    if not toks:
                        continue
                    for si, vals in enumerate(_parse_line(toks, self._var_dims)):
                        rows[si].append(vals)
        self._arrays = [np.asarray(r, np.float32) for r in rows]

    def local_shuffle(self):
        if self._arrays is None:
            raise ValueError("load_into_memory first")
        n = len(self._arrays[0])
        idx = native_feed.shuffle_indices(n, self._seed)
        self._seed += 1
        self._arrays = [
            native_feed.gather_collate(a, idx, self._thread) for a in self._arrays
        ]

    def global_shuffle(self, fleet=None, thread_num=None):
        """Single-controller SPMD loads per-process shards, so the local
        shuffle IS the global shuffle for this process's shard."""
        self.local_shuffle()

    def get_memory_data_size(self, fleet=None):
        return 0 if self._arrays is None else len(self._arrays[0])

    def release_memory(self):
        self._arrays = None

    # ---- iteration ---------------------------------------------------------
    def __iter__(self):
        if self._arrays is None:
            raise ValueError("load_into_memory first")
        n = len(self._arrays[0])
        bs = self._batch_size
        stop = (n // bs) * bs if self._drop_last else n
        for i in range(0, stop, bs):
            yield tuple(a[i:i + bs] for a in self._arrays)


class QueueDataset(InMemoryDataset):
    """Streaming variant (reference QueueDataset): files are parsed lazily
    per epoch instead of held resident; no shuffle (stream order)."""

    def load_into_memory(self):
        raise RuntimeError(
            "QueueDataset streams from files; use set_filelist + iterate "
            "(reference QueueDataset has no load_into_memory either)"
        )

    def local_shuffle(self):
        raise RuntimeError("QueueDataset cannot shuffle a stream (reference parity)")

    def __iter__(self):
        if not self._var_names:
            raise ValueError("call set_use_var first")
        batch = [[] for _ in self._var_names]
        for path in getattr(self, "_filelist", []):
            with open(path) as f:
                for line in f:
                    toks = line.split()
                    if not toks:
                        continue
                    for si, vals in enumerate(_parse_line(toks, self._var_dims)):
                        batch[si].append(vals)
                    if len(batch[0]) == self._batch_size:
                        yield tuple(np.asarray(b, np.float32) for b in batch)
                        batch = [[] for _ in self._var_names]
        if batch[0] and not self._drop_last:
            # the tail partial batch is data, not waste (drop_last opts out)
            yield tuple(np.asarray(b, np.float32) for b in batch)
