"""paddle.io parity: Dataset / Sampler / DataLoader.

Reference parity: python/paddle/io/__init__.py re-exporting
python/paddle/fluid/reader.py:311 (DataLoader) and fluid/dataloader/ in
/root/reference.
"""
from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
from .dataloader import DataLoader, default_collate_fn, get_worker_info  # noqa: F401
from .fleet_dataset import InMemoryDataset, QueueDataset  # noqa: F401


def batch(reader, batch_size, drop_last=False):
    """Legacy reader decorator (reference python/paddle/batch.py): wraps an
    item-yielding reader() into a batch-list-yielding reader()."""

    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched
