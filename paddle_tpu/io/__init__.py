"""paddle.io parity: Dataset / Sampler / DataLoader.

Reference parity: python/paddle/io/__init__.py re-exporting
python/paddle/fluid/reader.py:311 (DataLoader) and fluid/dataloader/ in
/root/reference.
"""
from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
from .dataloader import DataLoader, default_collate_fn, get_worker_info  # noqa: F401
